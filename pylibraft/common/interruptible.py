"""SIGINT-driven cooperative cancellation.

Ref: python/pylibraft/pylibraft/common/interruptible.pyx — a context
manager that installs a SIGINT handler calling
``raft::interruptible::cancel()`` on the captured token, so a blocked
``synchronize`` raises instead of hanging. Delegates to
:mod:`raft_tpu.core.interruptible`.
"""

from __future__ import annotations

import contextlib
import signal
import threading

from raft_tpu.core.interruptible import (  # noqa: F401 (re-exports)
    Interruptible,
    InterruptedException,
    synchronize,
)


@contextlib.contextmanager
def cuda_interruptible():
    """Ref: interruptible.pyx ``cuda_interruptible`` — cancel the current
    thread's token on SIGINT for the duration of the scope."""
    token = Interruptible.get_token()
    if threading.current_thread() is not threading.main_thread():
        # Signal handlers are main-thread only; nested scopes still get
        # cancellation via their parent's token.
        yield
        return
    prev = signal.getsignal(signal.SIGINT)
    if prev is None:
        # A non-Python (C-level) handler is installed: we could neither
        # chain to it nor restore it afterwards, so leave it untouched —
        # cancellation simply isn't hooked to SIGINT in this scope.
        yield
        return

    def handler(signum, frame):
        # Cancel the token (wakes worker threads blocked in synchronize),
        # then defer to the prior disposition: chain a Python handler, or
        # raise KeyboardInterrupt for the default — but respect an explicit
        # SIG_IGN (e.g. multiprocessing pool workers) and a non-Python
        # handler (getsignal() → None) by cancelling only.
        token.cancel()
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            raise KeyboardInterrupt

    signal.signal(signal.SIGINT, handler)
    try:
        yield
    finally:
        signal.signal(signal.SIGINT, prev)
        # A KeyboardInterrupt consumed by the caller must not leave the
        # cancel flag set — it would poison the next synchronize.
        token.reset()
