"""Candidate refinement (exact re-ranking), pylibraft surface.

Ref: python/pylibraft/pylibraft/neighbors/refine.pyx:173 (``refine``) →
raft::neighbors::refine (neighbors/refine.cuh). Returns
``(distances, indices)`` like the reference (refine.pyx:323).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.neighbors import refine as _refine_mod

# raft_tpu.neighbors re-exports the refine *function* under the same name;
# resolve to the module's callable either way.
_impl_refine = _refine_mod.refine if hasattr(_refine_mod, "refine") else _refine_mod

from pylibraft.common import auto_convert_output, auto_sync_handle, cai_wrapper
from pylibraft.neighbors.common import _get_metric


@auto_sync_handle
@auto_convert_output
def refine(dataset, queries, candidates, k=None, indices=None,
           distances=None, metric="sqeuclidean", handle=None):
    ds = cai_wrapper(dataset)
    q = cai_wrapper(queries)
    cand = cai_wrapper(candidates)
    if k is None:
        if indices is not None:
            k = np.asarray(indices).shape[1]
        elif distances is not None:
            k = np.asarray(distances).shape[1]
        else:
            raise ValueError("k must be given or deducible from indices/distances")

    d, i = _impl_refine(ds.array, q.array, cand.array, int(k),
                        metric=_get_metric(metric))
    if distances is not None and isinstance(distances, np.ndarray):
        np.copyto(distances, np.asarray(d))
    if indices is not None and isinstance(indices, np.ndarray):
        np.copyto(indices, np.asarray(i).astype(indices.dtype))
    return d, i
