"""Shared neighbors helpers, ref python/pylibraft/pylibraft/neighbors/
common.pyx (``_check_input_array``, ``_get_metric``)."""

from __future__ import annotations

import numpy as np

from raft_tpu.distance.distance_types import DistanceType

# ANN metric-name map, ref neighbors/common.pyx _get_metric: the ANN indexes
# accept only the three metrics below.
_METRIC_MAP = {
    "sqeuclidean": DistanceType.L2Expanded,
    "euclidean": DistanceType.L2SqrtExpanded,
    "inner_product": DistanceType.InnerProduct,
}

_METRIC_NAMES = {v: k for k, v in _METRIC_MAP.items()}


def _get_metric(metric) -> DistanceType:
    if isinstance(metric, DistanceType):
        return metric
    try:
        return _METRIC_MAP[metric]
    except KeyError:
        raise ValueError(
            f"metric {metric!r} is not supported; use one of "
            f"{sorted(_METRIC_MAP)}"
        ) from None


def _get_metric_string(metric: DistanceType) -> str:
    return _METRIC_NAMES.get(DistanceType(metric), str(metric))


def _check_input_array(cai, exp_dt, exp_rows=None, exp_cols=None):
    """Ref neighbors/common.pyx ``_check_input_array``: dtype whitelist +
    contiguity + optional shape pinning."""
    if np.dtype(cai.dtype) not in [np.dtype(dt) for dt in exp_dt]:
        raise TypeError("dtype %s not supported" % cai.dtype)
    if not cai.c_contiguous:
        raise ValueError("Row major input is expected")
    if exp_cols is not None and cai.shape[1] != exp_cols:
        raise ValueError(
            "Incorrect number of columns, expected {} got {}".format(
                exp_cols, cai.shape[1]
            )
        )
    if exp_rows is not None and cai.shape[0] != exp_rows:
        raise ValueError(
            "Incorrect number of rows, expected {} , got {}".format(
                exp_rows, cai.shape[0]
            )
        )
