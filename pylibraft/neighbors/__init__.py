"""pylibraft.neighbors — brute-force + ANN indexes.

Ref: python/pylibraft/pylibraft/neighbors/__init__.py (exports brute_force,
ivf_flat, ivf_pq, refine).
"""

from pylibraft.neighbors import brute_force, ivf_flat, ivf_pq
from pylibraft.neighbors.refine import refine

__all__ = ["brute_force", "ivf_flat", "ivf_pq", "refine"]
