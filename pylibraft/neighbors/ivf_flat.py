"""IVF-Flat ANN index, pylibraft surface.

Ref: python/pylibraft/pylibraft/neighbors/ivf_flat/ivf_flat.pyx —
``IndexParams``, ``Index``, ``build``, ``extend``, ``SearchParams``,
``search``, ``save``, ``load``. Backed by raft_tpu.neighbors.ivf_flat
(padded per-list storage + masked interleaved scan on TPU).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.neighbors import ivf_flat as _impl

from pylibraft.common import auto_convert_output, auto_sync_handle, cai_wrapper
from pylibraft.neighbors.common import (
    _check_input_array,
    _get_metric,
    _get_metric_string,
)


class IndexParams:
    """Ref ivf_flat.pyx IndexParams; metric accepts the ANN metric strings
    {"sqeuclidean", "euclidean", "inner_product"}. ``idx_dtype`` selects
    the neighbor-id dtype (the reference binds int64_t; int64 here
    requires jax_enable_x64, int32 is the TPU-fast default)."""

    def __init__(self, *, n_lists=1024, metric="sqeuclidean",
                 kmeans_n_iters=20, kmeans_trainset_fraction=0.5,
                 add_data_on_build=True, adaptive_centers=False,
                 idx_dtype="int32"):
        self.params = _impl.IndexParams(
            n_lists=n_lists,
            metric=_get_metric(metric),
            kmeans_n_iters=kmeans_n_iters,
            kmeans_trainset_fraction=kmeans_trainset_fraction,
            add_data_on_build=add_data_on_build,
            adaptive_centers=adaptive_centers,
            idx_dtype=idx_dtype,
        )

    @property
    def n_lists(self):
        return self.params.n_lists

    @property
    def metric(self):
        return _get_metric_string(self.params.metric)

    @property
    def kmeans_n_iters(self):
        return self.params.kmeans_n_iters

    @property
    def kmeans_trainset_fraction(self):
        return self.params.kmeans_trainset_fraction

    @property
    def add_data_on_build(self):
        return self.params.add_data_on_build

    @property
    def adaptive_centers(self):
        return self.params.adaptive_centers


class SearchParams:
    """Ref ivf_flat.pyx SearchParams(n_probes=20)."""

    def __init__(self, *, n_probes=20):
        self.params = _impl.SearchParams(n_probes=n_probes)

    @property
    def n_probes(self):
        return self.params.n_probes

    def __repr__(self):
        return f"SearchParams(n_probes={self.n_probes})"


class Index:
    """Handle over the device-resident index (ref ivf_flat.pyx Index)."""

    def __init__(self, index=None):
        self._index = index
        self.trained = index is not None

    @property
    def size(self):
        return 0 if self._index is None else self._index.size

    @property
    def dim(self):
        return 0 if self._index is None else self._index.dim

    @property
    def n_lists(self):
        return 0 if self._index is None else self._index.n_lists

    @property
    def metric(self):
        return None if self._index is None else _get_metric_string(self._index.metric)

    @property
    def adaptive_centers(self):
        return False if self._index is None else self._index.adaptive_centers

    def __repr__(self):
        attrs = ", ".join(
            f"{k}={getattr(self, k)}"
            for k in ["size", "dim", "n_lists", "metric"])
        return f"Index(type=IVF-Flat, {attrs})"


@auto_sync_handle
@auto_convert_output
def build(index_params: IndexParams, dataset, handle=None) -> Index:
    """Ref ivf_flat.pyx ``build`` — trains balanced kmeans centers and fills
    the inverted lists."""
    ds = cai_wrapper(dataset)
    _check_input_array(ds, [np.dtype("float32"), np.dtype("byte"),
                            np.dtype("ubyte")])
    return Index(_impl.build(index_params.params, ds.array))


@auto_sync_handle
@auto_convert_output
def extend(index: Index, new_vectors, new_indices, handle=None) -> Index:
    """Ref ivf_flat.pyx ``extend``."""
    v = cai_wrapper(new_vectors)
    i = cai_wrapper(new_indices)
    _check_input_array(v, [np.dtype("float32"), np.dtype("byte"),
                           np.dtype("ubyte")], exp_cols=index.dim)
    index._index = _impl.extend(index._index, v.array, i.array)
    return index


@auto_sync_handle
@auto_convert_output
def search(search_params: SearchParams, index: Index, queries, k: int,
           neighbors=None, distances=None, memory_resource=None, handle=None):
    # memory_resource is accepted for API parity with the reference binding
    # (ivf_pq.pyx:568 takes an RMM memory resource); allocation here is
    # managed by XLA, so the knob is a no-op.
    """Ref ivf_flat.pyx ``search`` — returns ``(distances, neighbors)``."""
    if not index.trained:
        raise ValueError("Index needs to be built before calling search.")
    q = cai_wrapper(queries)
    _check_input_array(q, [np.dtype("float32")], exp_cols=index.dim)
    d, n = _impl.search(search_params.params, index._index, q.array, k)
    if distances is not None and isinstance(distances, np.ndarray):
        np.copyto(distances, np.asarray(d))
    if neighbors is not None and isinstance(neighbors, np.ndarray):
        np.copyto(neighbors, np.asarray(n).astype(neighbors.dtype))
    return d, n


def save(filename: str, index: Index, handle=None) -> None:
    """Ref ivf_flat.pyx ``save`` → versioned serialized index."""
    _impl.save(filename, index._index)


def load(filename: str, handle=None) -> Index:
    """Ref ivf_flat.pyx ``load``."""
    return Index(_impl.load(filename))
