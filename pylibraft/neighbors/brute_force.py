"""Brute-force exact kNN, pylibraft surface.

Ref: python/pylibraft/pylibraft/neighbors/brute_force.pyx:75 (``knn``) →
raft::runtime brute-force (cpp/src/neighbors/brute_force_knn_int64_t_float.cu)
→ tiled pairwise + select_k (neighbors/detail/knn_brute_force.cuh:51).
TPU path: raft_tpu.neighbors.brute_force (fused L2 matmul + top-k tiles).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.neighbors import brute_force as _bf

from pylibraft.common import auto_convert_output, auto_sync_handle, cai_wrapper
from pylibraft.distance.pairwise_distance import DISTANCE_TYPES


@auto_sync_handle
@auto_convert_output
def knn(dataset, queries, k=None, indices=None, distances=None,
        metric="sqeuclidean", metric_arg=2.0, global_id_offset=0,
        idx_dtype="int32", handle=None):
    """Exact nearest neighbors; returns ``(distances, indices)`` like the
    reference (brute_force.pyx:179).

    Examples
    --------
    >>> import numpy as np
    >>> from pylibraft.neighbors.brute_force import knn
    >>> db = np.array([[0.0], [1.0], [5.0]], np.float32)
    >>> q = np.array([[0.9]], np.float32)
    >>> d, i = knn(db, q, k=2)
    >>> np.asarray(i).tolist()
    [[1, 0]]
    """
    ds = cai_wrapper(dataset)
    q = cai_wrapper(queries)
    if k is None:
        if indices is not None:
            k = np.asarray(indices).shape[1]
        elif distances is not None:
            k = np.asarray(distances).shape[1]
        else:
            raise ValueError("k must be given or deducible from indices/distances")

    metric_dt = DISTANCE_TYPES[metric] if isinstance(metric, str) else metric
    # idx_dtype="int64" matches the reference's int64_t binding
    # (brute_force_knn_int64_t_float.cu); requires jax_enable_x64.
    d, i = _bf.knn(ds.array, q.array, int(k), metric=metric_dt,
                   metric_arg=metric_arg, global_id_offset=global_id_offset,
                   idx_dtype=idx_dtype)

    if distances is not None and isinstance(distances, np.ndarray):
        np.copyto(distances, np.asarray(d))
    if indices is not None and isinstance(indices, np.ndarray):
        np.copyto(indices, np.asarray(i).astype(indices.dtype))
    return d, i
