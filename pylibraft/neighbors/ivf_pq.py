"""IVF-PQ ANN index, pylibraft surface.

Ref: python/pylibraft/pylibraft/neighbors/ivf_pq/ivf_pq.pyx — IndexParams
(:91), Index (:227), build (:309), extend (:406), SearchParams (:511),
search (:568), save (:719), load (:765). Backed by
raft_tpu.neighbors.ivf_pq (MXU codebook training, packed uint8 codes,
LUT-free one-hot scoring on TPU).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.neighbors import ivf_pq as _impl

from pylibraft.common import auto_convert_output, auto_sync_handle, cai_wrapper
from pylibraft.neighbors.common import (
    _check_input_array,
    _get_metric,
    _get_metric_string,
)

_CODEBOOK_KINDS = {
    "subspace": _impl.CodebookGen.PER_SUBSPACE,
    "cluster": _impl.CodebookGen.PER_CLUSTER,
}
_DTYPE_NAMES = {
    "float32": np.float32, "float16": np.float16, "bfloat16": "bfloat16",
    # The reference's fp8 LUT maps to the affine uint8-quantized LUT
    # (ivf_pq_search.cuh:70 fp_8bit analog; see raft_tpu ivf_pq.SearchParams).
    "fp8": np.uint8,
}


class IndexParams:
    """Ref ivf_pq.pyx:91-226; same names/defaults."""

    def __init__(self, *, n_lists=1024, metric="sqeuclidean",
                 kmeans_n_iters=20, kmeans_trainset_fraction=0.5,
                 pq_bits=8, pq_dim=0, codebook_kind="subspace",
                 force_random_rotation=False, add_data_on_build=True,
                 conservative_memory_allocation=False, idx_dtype="int32",
                 retain_dataset=True):
        if codebook_kind not in _CODEBOOK_KINDS:
            raise ValueError(f"codebook_kind must be in {sorted(_CODEBOOK_KINDS)}")
        self.params = _impl.IndexParams(
            n_lists=n_lists,
            metric=_get_metric(metric),
            kmeans_n_iters=kmeans_n_iters,
            kmeans_trainset_fraction=kmeans_trainset_fraction,
            pq_bits=pq_bits,
            pq_dim=pq_dim,
            codebook_kind=_CODEBOOK_KINDS[codebook_kind],
            force_random_rotation=force_random_rotation,
            add_data_on_build=add_data_on_build,
            idx_dtype=idx_dtype,
            conservative_memory_allocation=conservative_memory_allocation,
            retain_dataset=retain_dataset,
        )

    @property
    def n_lists(self):
        return self.params.n_lists

    @property
    def metric(self):
        return _get_metric_string(self.params.metric)

    @property
    def kmeans_n_iters(self):
        return self.params.kmeans_n_iters

    @property
    def kmeans_trainset_fraction(self):
        return self.params.kmeans_trainset_fraction

    @property
    def pq_bits(self):
        return self.params.pq_bits

    @property
    def pq_dim(self):
        return self.params.pq_dim

    @property
    def codebook_kind(self):
        kind = self.params.codebook_kind
        return "subspace" if kind == _impl.CodebookGen.PER_SUBSPACE else "cluster"

    @property
    def force_random_rotation(self):
        return self.params.force_random_rotation

    @property
    def add_data_on_build(self):
        return self.params.add_data_on_build

    @property
    def conservative_memory_allocation(self):
        return self.params.conservative_memory_allocation


class SearchParams:
    """Ref ivf_pq.pyx:511-565 (n_probes, lut_dtype,
    internal_distance_dtype)."""

    def __init__(self, *, n_probes=20, lut_dtype=np.float32,
                 internal_distance_dtype=np.float32, min_recall=None):
        lut = _DTYPE_NAMES.get(str(lut_dtype), lut_dtype)
        internal = _DTYPE_NAMES.get(str(internal_distance_dtype),
                                    internal_distance_dtype)
        self.params = _impl.SearchParams(
            n_probes=n_probes, lut_dtype=lut,
            internal_distance_dtype=internal, min_recall=min_recall)

    @property
    def n_probes(self):
        return self.params.n_probes

    @property
    def min_recall(self):
        return self.params.min_recall

    @property
    def lut_dtype(self):
        return self.params.lut_dtype

    @property
    def internal_distance_dtype(self):
        return self.params.internal_distance_dtype

    def __repr__(self):
        return f"SearchParams(n_probes={self.n_probes})"


class Index:
    """Ref ivf_pq.pyx:227-305."""

    def __init__(self, index=None):
        self._index = index
        self.trained = index is not None

    @property
    def size(self):
        return 0 if self._index is None else self._index.size

    @property
    def dim(self):
        return 0 if self._index is None else self._index.dim

    @property
    def pq_dim(self):
        return 0 if self._index is None else self._index.pq_dim

    @property
    def pq_len(self):
        return 0 if self._index is None else self._index.pq_len

    @property
    def pq_bits(self):
        return 0 if self._index is None else self._index.pq_bits

    @property
    def rot_dim(self):
        return 0 if self._index is None else self._index.rot_dim

    @property
    def n_lists(self):
        return 0 if self._index is None else self._index.n_lists

    @property
    def metric(self):
        return None if self._index is None else _get_metric_string(self._index.metric)

    @property
    def codebook_kind(self):
        if self._index is None:
            return None
        kind = self._index.codebook_kind
        return "subspace" if kind == _impl.CodebookGen.PER_SUBSPACE else "cluster"

    def __repr__(self):
        attrs = ", ".join(
            f"{k}={getattr(self, k)}"
            for k in ["size", "dim", "pq_dim", "pq_bits", "n_lists", "metric"])
        return f"Index(type=IVF-PQ, {attrs})"


@auto_sync_handle
@auto_convert_output
def build(index_params: IndexParams, dataset, handle=None) -> Index:
    """Ref ivf_pq.pyx:309 — trainset subsample → balanced kmeans →
    per-subspace/per-cluster codebooks → encode+fill lists."""
    ds = cai_wrapper(dataset)
    _check_input_array(ds, [np.dtype("float32"), np.dtype("byte"),
                            np.dtype("ubyte")])
    return Index(_impl.build(index_params.params, ds.array))


@auto_sync_handle
@auto_convert_output
def extend(index: Index, new_vectors, new_indices, handle=None) -> Index:
    """Ref ivf_pq.pyx:406."""
    v = cai_wrapper(new_vectors)
    i = cai_wrapper(new_indices)
    _check_input_array(v, [np.dtype("float32"), np.dtype("byte"),
                           np.dtype("ubyte")], exp_cols=index.dim)
    index._index = _impl.extend(index._index, v.array, i.array)
    return index


@auto_sync_handle
@auto_convert_output
def search(search_params: SearchParams, index: Index, queries, k: int,
           neighbors=None, distances=None, memory_resource=None, handle=None):
    # memory_resource is accepted for API parity with the reference binding
    # (ivf_pq.pyx:568 takes an RMM memory resource); allocation here is
    # managed by XLA, so the knob is a no-op.
    """Ref ivf_pq.pyx:568 — returns ``(distances, neighbors)``."""
    if not index.trained:
        raise ValueError("Index needs to be built before calling search.")
    q = cai_wrapper(queries)
    _check_input_array(q, [np.dtype("float32")], exp_cols=index.dim)
    d, n = _impl.search(search_params.params, index._index, q.array, k)
    if distances is not None and isinstance(distances, np.ndarray):
        np.copyto(distances, np.asarray(d))
    if neighbors is not None and isinstance(neighbors, np.ndarray):
        np.copyto(neighbors, np.asarray(n).astype(neighbors.dtype))
    return d, n


def save(filename: str, index: Index, handle=None) -> None:
    """Ref ivf_pq.pyx:719 — versioned binary serialization."""
    _impl.save(filename, index._index)


def load(filename: str, handle=None) -> Index:
    """Ref ivf_pq.pyx:765."""
    return Index(_impl.load(filename))
