"""pylibraft.random — RMAT graph generator.

Ref: python/pylibraft/pylibraft/random/__init__.py (exports ``rmat``) over
rmat_rectangular_generator.pyx:80.
"""

from pylibraft.random.rmat_rectangular_generator import rmat

__all__ = ["rmat"]
