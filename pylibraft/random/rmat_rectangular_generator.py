"""RMAT rectangular graph generator, pylibraft surface.

Ref: python/pylibraft/pylibraft/random/rmat_rectangular_generator.pyx:80
(``rmat(out, theta, r_scale, c_scale, seed)``) → raft::random::
rmat_rectangular_gen (cpp/src/random/rmat_rectangular_generator.cu).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.random.rmat import rmat_rectangular_gen as _gen
from raft_tpu.random.rng_state import RngState

from pylibraft.common import auto_convert_output, auto_sync_handle, cai_wrapper


@auto_sync_handle
@auto_convert_output
def rmat(out, theta, r_scale, c_scale, seed=12345, handle=None):
    """Fill ``out`` (n_edges, 2) with RMAT edges; returns out. Same in-place
    contract as the reference (out dtype int32/int64)."""
    t = cai_wrapper(theta)
    n_edges = np.asarray(out).shape[0] if not hasattr(out, "shape") else out.shape[0]
    src, dst = _gen(RngState(seed=int(seed)), t.array, int(r_scale),
                    int(c_scale), int(n_edges))
    edges = np.stack([np.asarray(src), np.asarray(dst)], axis=1)
    if isinstance(out, np.ndarray):
        np.copyto(out, edges.astype(out.dtype))
        return out
    if hasattr(out, "_array"):
        import jax.numpy as jnp

        out._array = jnp.asarray(edges.astype(out.dtype))
        return out
    return edges
