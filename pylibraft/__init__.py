"""pylibraft-compatible API surface over the TPU-native ``raft_tpu`` core.

Mirrors the module layout and entry points of the reference's
``python/pylibraft`` package (Cython over ``raft::runtime``), so code written
against pylibraft runs on TPU unchanged modulo the array types: inputs are
anything NumPy/JAX can ingest (``__array__``, ``__cuda_array_interface__`` is
replaced by jax Arrays living in HBM), outputs are ``device_ndarray`` wrappers
over jax Arrays.

Ref layout: python/pylibraft/pylibraft/{common,distance,neighbors,cluster,
random}.
"""

__version__ = "23.04.00+tpu"

from pylibraft import cluster, common, distance, neighbors, random  # noqa: E402,F401
