"""Runtime-metric pairwise distance, pylibraft surface.

Ref: python/pylibraft/pylibraft/distance/pairwise_distance.pyx:62-83
(metric-name dict) and :93 (``def distance``) → raft::runtime::distance::
pairwise_distance (cpp/src/distance/pairwise_distance.cu). On TPU the
expanded metrics are a single MXU gram matmul + norms epilogue, unexpanded
metrics a blocked elementwise reduction (raft_tpu.distance.pairwise).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.distance import pairwise as _pairwise
from raft_tpu.distance.distance_types import DISTANCE_TYPES, DistanceType

from pylibraft.common import auto_convert_output, auto_sync_handle, cai_wrapper

SUPPORTED_DISTANCES = [
    "euclidean", "l1", "cityblock", "l2", "inner_product", "chebyshev",
    "minkowski", "canberra", "kl_divergence", "correlation", "russellrao",
    "hellinger", "lp", "hamming", "jensenshannon", "cosine", "sqeuclidean",
]


@auto_sync_handle
@auto_convert_output
def distance(X, Y, out=None, metric="euclidean", p=2.0, handle=None):
    """Compute pairwise distances between X and Y; ref
    distance/pairwise_distance.pyx:93-171. ``out``, when given, receives the
    result (host copy for numpy outputs) and is returned.

    Examples
    --------
    >>> import numpy as np
    >>> from pylibraft.distance import pairwise_distance
    >>> X = np.array([[0.0, 0.0], [3.0, 4.0]], np.float32)
    >>> Y = np.array([[0.0, 0.0]], np.float32)
    >>> np.asarray(pairwise_distance(X, Y, metric="euclidean")).round(2)
    array([[0.],
           [5.]], dtype=float32)
    """
    if isinstance(metric, str):
        if metric not in DISTANCE_TYPES:
            raise ValueError(f"metric {metric!r} is not supported")
        metric_dt = DISTANCE_TYPES[metric]
    else:
        metric_dt = DistanceType(metric)

    x = cai_wrapper(X)
    y = cai_wrapper(Y)
    if x.shape[1] != y.shape[1]:
        raise ValueError("Inputs must have same number of columns")

    d = _pairwise.distance(x.array, y.array, metric=metric_dt, metric_arg=p)

    if out is not None:
        if isinstance(out, np.ndarray):
            np.copyto(out, np.asarray(d))
        elif hasattr(out, "_array"):
            out._array = d.astype(out._array.dtype)
        return out
    return d


pairwise_distance = distance
