"""pylibraft.distance — pairwise distances + fused L2 argmin.

Ref: python/pylibraft/pylibraft/distance/__init__.py (exports
``distance``/``pairwise_distance``, ``fused_l2_nn_argmin``,
``DISTANCE_TYPES``).
"""

from pylibraft.distance.pairwise_distance import (
    DISTANCE_TYPES,
    SUPPORTED_DISTANCES,
    distance,
    pairwise_distance,
)
from pylibraft.distance.fused_l2_nn import fused_l2_nn_argmin

__all__ = [
    "DISTANCE_TYPES",
    "SUPPORTED_DISTANCES",
    "distance",
    "fused_l2_nn_argmin",
    "pairwise_distance",
]
