"""Fused L2 nearest-neighbor argmin, pylibraft surface.

Ref: python/pylibraft/pylibraft/distance/fused_l2_nn.pyx:66
(``fused_l2_nn_argmin(X, Y, out=None, sqrt=True)``) →
raft::runtime ``fused_l2_nn_min_arg`` (cpp/src/distance/fused_l2_min_arg.cu).
TPU path: one MXU matmul + argmin epilogue (raft_tpu.distance.fused_l2_nn).
"""

from __future__ import annotations

import numpy as np

from raft_tpu.distance.fused_l2_nn import fused_l2_nn_argmin as _argmin

from pylibraft.common import auto_convert_output, auto_sync_handle, cai_wrapper


@auto_sync_handle
@auto_convert_output
def fused_l2_nn_argmin(X, Y, out=None, sqrt=True, handle=None):
    """For each row of X, the index of the nearest row of Y (int32)."""
    x = cai_wrapper(X)
    y = cai_wrapper(Y)
    if x.shape[1] != y.shape[1]:
        raise ValueError("Inputs must have same number of columns")
    idx = _argmin(x.array, y.array, sqrt=sqrt)
    if out is not None:
        if isinstance(out, np.ndarray):
            np.copyto(out, np.asarray(idx))
        elif hasattr(out, "_array"):
            out._array = idx.astype(out._array.dtype)
        return out
    return idx
