#!/usr/bin/env python
"""Headline benchmark: brute-force k-NN QPS (fused L2 + top-k) on SIFT-like
data — BASELINE.json config #2.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference repo publishes no benchmark numbers (BASELINE.md — RAFT 23.04
has only gbench microbenchmarks, no results tables), so ``vs_baseline``
compares against a CPU/NumPy exact-kNN implementation of the same workload
measured in-process — the honest available baseline on this hardware.

Timing methodology: the device link (axon tunnel) has ~100 ms round-trip
latency per synchronized call and ``block_until_ready`` does not reliably
fence it, so the workload is iterated R times *inside one jit* via
``lax.scan`` over R distinct query batches and synced once with a host
transfer. Per-iteration time = total / R with the link overhead amortized
(the analog of the reference's cudaEvent timing with L2-flush between
iterations, cpp/bench/common/benchmark.hpp:93-148).
"""

import json
import sys
import time

import numpy as np


def _sift_like(n_db=10_000, n_q=1_000, dim=128, seed=0, n_sets=256):
    """SIFT-10K-shaped synthetic data (uint8-range descriptors); n_sets
    distinct query batches so repeated iterations cannot be cached or
    hoisted out of the scan. n_sets=256 amortizes the ~100 ms axon-link
    round-trip to <0.4 ms/iteration."""
    rng = np.random.default_rng(seed)
    db = rng.integers(0, 256, size=(n_db, dim)).astype(np.float32)
    qs = rng.integers(0, 256, size=(n_sets, n_q, dim)).astype(np.float32)
    return db, qs


def _numpy_knn_qps(db, q, k, reps=3):
    def run():
        d = (
            (q * q).sum(1)[:, None]
            + (db * db).sum(1)[None, :]
            - 2.0 * q @ db.T
        )
        idx = np.argpartition(d, k, axis=1)[:, :k]
        return idx

    run()
    t0 = time.perf_counter()
    for _ in range(reps):
        run()
    dt = (time.perf_counter() - t0) / reps
    return q.shape[0] / dt


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from raft_tpu.neighbors import brute_force

    k = 10
    db_h, qs_h = _sift_like()
    db = jax.device_put(db_h)
    qs = jax.device_put(qs_h)

    @jax.jit
    def run_all(qs, db):
        def body(acc, q):
            d, i = brute_force.knn(db, q, k)
            return acc + d[0, 0] + i[0, 0].astype(jnp.float32), None
        acc, _ = lax.scan(body, jnp.float32(0), qs)
        # Keep only the first batch's full results (correctness gate) — at
        # n_sets=256, stacking every (d, i) would carry 256× dead outputs.
        d0, i0 = brute_force.knn(db, qs[0], k)
        return acc, d0, i0

    # Warmup (compile) + one synced run, then timed runs (sync via host
    # transfer of the checksum scalar).
    acc, d0, i0 = run_all(qs, db)
    np.asarray(acc)
    R = qs.shape[0]
    best = np.inf
    for _ in range(4):
        t0 = time.perf_counter()
        acc, d0, i0 = run_all(qs, db)
        np.asarray(acc)
        best = min(best, (time.perf_counter() - t0) / R)
    qps = qs.shape[1] / best

    # Correctness gate: recall@10 == 1.0 vs exact NumPy ground truth on the
    # first query batch.
    q0 = qs_h[0]
    dn = ((q0 * q0).sum(1)[:, None] + (db_h * db_h).sum(1)[None, :]
          - 2.0 * q0 @ db_h.T)
    truth = np.argsort(dn, axis=1)[:, :k]
    found = np.asarray(i0)
    hits = sum(len(np.intersect1d(found[r], truth[r]))
               for r in range(q0.shape[0]))
    recall = hits / truth.size
    if recall < 0.999:
        print(json.dumps({"metric": "bf_knn_sift10k_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": f"recall {recall:.4f} < 1.0"}))
        sys.exit(1)

    cpu_qps = _numpy_knn_qps(db_h, q0, k)
    print(json.dumps({
        "metric": "bf_knn_sift10k_qps",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 3),
    }))


if __name__ == "__main__":
    main()
