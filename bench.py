#!/usr/bin/env python
"""Round benchmark: one JSON line per tracked metric, headline LAST.

The driver parses the final stdout line ({"metric", "value", "unit",
"vs_baseline"}); the preceding lines carry the rest of the tracked family
(distance, select_k, fused_l2_nn, IVF search at 100K and 1M, 1M build,
balanced k-means, sparse) so BENCH_r*.json records round-over-round
movement for the whole surface (the gbench-family role of cpp/bench/*).

Regression-grade contract (round 3): every scan metric is the median of
>=5 repeats with the measured link RTT subtracted (see bench/common.py —
the additive RTT/iters error was the root cause of the round-2
"regressions"), emits its spread, and compute-bound metrics carry an
achieved-FLOP/s + MFU column (vs the v5e bf16 peak, 197 TFLOP/s — f32
paths run the MXU in multi-pass mode and are expected to sit well below
it). Engines and capacities are pinned so the numbers measure the chip,
not dispatch heuristics.

``vs_baseline`` is the ratio against the round-1 measured value of the
same config (BASELINE.md round-1 table; those values carried the
round-1 harness's RTT error, so corrected metrics can legitimately jump
— the note in BASELINE.md explains). Metrics new this round report
vs_baseline = 1.0.
"""

import json
import sys
import time

import numpy as np

# Round-1 measured values (BASELINE.md) for vs_baseline ratios.
_R1 = {
    "pairwise_cosine_2048_gpairs": 2.9,        # G pairs/s
    "select_k_b1000_l10000_krows": 372_000.0,  # rows/s
    "select_k_b64_l131072_k128_krows": 13_600.0,
    "fused_l2_nn_8192x1024_rows": 4_400_000.0, # rows/s
    "ivf_flat_search_100k_qps": 56_000.0,      # best round-1 bucketed
    "ivf_pq_search_100k_qps": 32_000.0,
    "kmeans_balanced_fit_100k_s": 6.6,         # best round-1 wall seconds
}

_BF16_PEAK = 197e12  # v5e bf16 MXU peak FLOP/s


def _emit(metric, value, unit, vs, **extra):
    rec = {"metric": metric, "value": round(float(value), 1),
           "unit": unit, "vs_baseline": round(float(vs), 3)}
    for k, v in extra.items():
        rec[k] = round(float(v), 4) if isinstance(v, float) else v
    print(json.dumps(rec), flush=True)


def _spread(st):
    return round((st["max_s"] - st["min_s"]) / max(st["median_s"], 1e-12)
                 * 100, 1)


def _eager_qps(fn, q, reps=16, rounds=7):
    """Pipelined eager dispatch + one fence per round, RTT-corrected —
    the shared timing protocol of the 1M/4M/SIFT families (a 1M search
    wrapped in a measurement lax.scan crashes the axon worker). QPS is
    per row of ``q``.

    Outlier-robust (VERDICT r4 weak #1: one tunnel-stall round made a
    tracked spread read 908%): ≥7 rounds, rounds beyond 5 MADs from the
    median are rejected (the reference's bench flushes L2 + times with
    events for the same reason, cpp/bench/common/benchmark.hpp:93-148),
    and the reported spread is that of the surviving rounds."""
    from bench.common import fence, link_rtt

    out = fn(q)
    fence(out)
    times = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(reps):
            out = fn(q)
        fence(out)
        times.append((time.perf_counter() - t0 - link_rtt()) / reps)
    t = np.sort(np.asarray(times))
    med = float(np.median(t))
    mad = float(np.median(np.abs(t - med)))
    keep = t[np.abs(t - med) <= max(5.0 * mad, 0.02 * med)]
    med = float(np.median(keep))
    return q.shape[0] / med, (keep[-1] - keep[0]) / med * 100


def _family():
    import jax
    import jax.numpy as jnp

    from bench.common import scan_stats, wall_stats
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.cluster.kmeans_types import KMeansBalancedParams
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_min_reduce
    from raft_tpu.distance.pairwise import distance as pairwise
    from raft_tpu.distance.distance_types import DistanceType
    from raft_tpu.matrix.select_k import select_k
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    from raft_tpu.random.make_blobs import make_blobs

    rng = np.random.default_rng(0)

    # -- pairwise cosine: round-1 shape (2048^2 x 128), a compute-bound
    # shape (8192^2 x 256), and the same at bf16 MXU precision (the knob
    # users flip when ~1e-3 relative error is acceptable) — the MFU
    # evidence VERDICT r2 weak #2 asked for.
    for (m, d, prec, name, r1) in (
            (2048, 128, "highest", "pairwise_cosine_2048_gpairs",
             _R1["pairwise_cosine_2048_gpairs"]),
            (8192, 256, "highest", "pairwise_cosine_8192x256_gpairs", None),
            (8192, 256, "default", "pairwise_cosine_8192x256_bf16_gpairs",
             None)):
        a = jnp.asarray(rng.normal(size=(m, d)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(m, d)).astype(np.float32))
        st = scan_stats(
            lambda x, y, p=prec: pairwise(
                x, y, metric=DistanceType.CosineExpanded, precision=p),
            a, (b,))
        s = st["median_s"]
        v = m * m / s / 1e9
        flops = 2.0 * m * m * d / s
        _emit(name, v, "Gpairs/s", v / r1 if r1 else 1.0,
              spread_pct=_spread(st), flops_t=flops / 1e12,
              mfu_pct=round(flops / _BF16_PEAK * 100, 2))

    # -- select_k: round-1 small shape + the large-len stream-engine shape.
    m = jnp.asarray(rng.normal(size=(1000, 10000)).astype(np.float32))
    st = scan_stats(lambda x: select_k(x, 10), m)
    v = 1000 / st["median_s"]
    _emit("select_k_b1000_l10000_krows", v, "rows/s",
          v / _R1["select_k_b1000_l10000_krows"], spread_pct=_spread(st))

    m = jnp.asarray(rng.normal(size=(64, 131072)).astype(np.float32))
    st = scan_stats(lambda x: select_k(x, 128), m)
    v = 64 / st["median_s"]
    _emit("select_k_b64_l131072_k128_krows", v, "rows/s",
          v / _R1["select_k_b64_l131072_k128_krows"],
          spread_pct=_spread(st))

    # -- fused_l2_nn (the k-means inner loop)
    x = jnp.asarray(rng.normal(size=(8192, 64)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(1024, 64)).astype(np.float32))
    st = scan_stats(lambda q: fused_l2_nn_min_reduce(q, y), x)
    s = st["median_s"]
    v = 8192 / s
    flops = 2.0 * 8192 * 1024 * 64 / s
    _emit("fused_l2_nn_8192x1024_rows", v, "rows/s",
          v / _R1["fused_l2_nn_8192x1024_rows"], spread_pct=_spread(st),
          flops_t=flops / 1e12,
          mfu_pct=round(flops / _BF16_PEAK * 100, 2))

    # -- IVF search QPS at 100K x 128, pinned tuned engine, measured as a
    # jitted scan over perturbed query batches (searches are traceable
    # with an explicit bucket_cap), so the number excludes dispatch. The
    # index tensors ride as scan_stats ``extra`` arguments — a closure
    # would bake them into the program as constants (tens of MB of HLO).
    X, _ = make_blobs(100_000, 128, n_clusters=200, seed=3)
    Q = X[:1000]
    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=256), X)
    spf = ivf_flat.SearchParams(n_probes=32, engine="bucketed",
                                bucket_cap=128)

    def flat_search(q, centers, data, indices, sizes):
        idx = ivf_flat.Index(metric=fidx.metric, centers=centers,
                             data=data, indices=indices, list_sizes=sizes)
        return ivf_flat.search(spf, idx, q, 10)

    st = scan_stats(flat_search, Q,
                    (fidx.centers, fidx.data, fidx.indices,
                     fidx.list_sizes))
    v = 1000 / st["median_s"]
    _emit("ivf_flat_search_100k_qps", v, "qps",
          v / _R1["ivf_flat_search_100k_qps"], spread_pct=_spread(st))

    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=256), X)
    recon = pidx.reconstructed()  # decode once, outside the scan
    spq = ivf_pq.SearchParams(n_probes=32, engine="bucketed", bucket_cap=128)

    def pq_search(q, centers, rot, books, codes, indices, sizes, rec):
        idx = ivf_pq.Index(metric=pidx.metric,
                           codebook_kind=pidx.codebook_kind,
                           centers=centers, rotation_matrix=rot,
                           pq_centers=books, pq_codes=codes,
                           indices=indices, list_sizes=sizes,
                           pq_bits=pidx.pq_bits, pq_dim=pidx.pq_dim,
                           _recon=rec)
        return ivf_pq.search(spq, idx, q, 10)

    st = scan_stats(pq_search, Q,
                    (pidx.centers, pidx.rotation_matrix, pidx.pq_centers,
                     pidx.pq_codes, pidx.indices, pidx.list_sizes, recon))
    v = 1000 / st["median_s"]
    _emit("ivf_pq_search_100k_qps", v, "qps",
          v / _R1["ivf_pq_search_100k_qps"], spread_pct=_spread(st))
    del fidx, pidx, X, Q, recon

    # -- fused_l2_nn acceptance shape (VERDICT r4 item 3: >=15% MFU at
    # 8192x4096x128-class shapes, spread <=15%) — the Pallas kernel path.
    x = jnp.asarray(rng.normal(size=(8192, 128)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(4096, 128)).astype(np.float32))
    st = scan_stats(lambda q: fused_l2_nn_min_reduce(q, y), x)
    s = st["median_s"]
    flops = 2.0 * 8192 * 4096 * 128 / s
    _emit("fused_l2_nn_8192x4096x128_rows", 8192 / s, "rows/s", 1.0,
          spread_pct=_spread(st), flops_t=flops / 1e12,
          mfu_pct=round(flops / _BF16_PEAK * 100, 2))

    # -- balanced k-means fit: fence-timed wall (block_until_ready does
    # not fence on axon — wall_stats under-measured with 100%+ spread,
    # VERDICT r3 weak #5; vs_baseline = speedup r1/now)
    from bench.common import fence

    Xk, _ = make_blobs(100_000, 64, n_clusters=100, seed=7)
    p = KMeansBalancedParams(n_iters=10)
    for _ in range(2):                          # compile + steady-state
        c = kmeans_balanced.fit(p, Xk, 512)     # warm (the first timed
        fence(c)                                # fit after compile still
    fits = []                                   # carries a ~2x outlier)
    for _ in range(5):
        t0 = time.perf_counter()
        c = kmeans_balanced.fit(p, Xk, 512)
        fence(c)
        fits.append(time.perf_counter() - t0)
    fits.sort()
    med = float(np.median(fits))
    _emit("kmeans_balanced_fit_100k_s", med, "s",
          _R1["kmeans_balanced_fit_100k_s"] / med,
          spread_pct=round((fits[-1] - fits[0]) / med * 100, 1))
    del Xk

    # -- sparse pairwise L2 at 50K dims (block-staged engine)
    from raft_tpu.sparse import distance as sparse_distance
    from raft_tpu.sparse.types import CSR

    d_sp, nnz_row, rows = 50_000, 50, 2048
    cols = rng.integers(0, d_sp, size=rows * nnz_row).astype(np.int32)
    valsv = rng.normal(size=rows * nnz_row).astype(np.float32)
    indptr = np.arange(0, rows * nnz_row + 1, nnz_row, dtype=np.int32)
    ca = CSR(jnp.asarray(indptr), jnp.asarray(cols), jnp.asarray(valsv),
             (rows, d_sp))
    st = wall_stats(lambda: sparse_distance.pairwise_distance(
        ca, ca, metric="euclidean"))
    _emit("sparse_l2_2048x50kd_s", st["median_s"], "s", 1.0,
          spread_pct=_spread(st))
    del ca


def _recall(found, truth):
    k = truth.shape[1]
    return float(np.mean([len(np.intersect1d(found[r], truth[r])) / k
                          for r in range(truth.shape[0])]))


def _family_1m():
    """1M-scale build + QPS-at-recall, the driver-tracked record of what
    BASELINE.md narrates (VERDICT r2 #3). Clustered queries are the
    recall=1.0 regime; uniform queries the structureless worst case."""
    import jax
    import jax.numpy as jnp

    from bench.common import fence, scan_stats
    from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq
    from raft_tpu.random.make_blobs import make_blobs

    rng = np.random.default_rng(11)
    X, _ = make_blobs(1_000_000, 128, n_clusters=1000, seed=5,
                      cluster_std=5.0)
    fence(X)

    # Build wall time: median of 3 timed builds after the compile warm
    # (the first call includes any residual compiles; reported alongside).
    t0 = time.perf_counter()
    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024), X)
    fence(fidx.data)
    warm = time.perf_counter() - t0
    builds = []
    for _ in range(3):
        fidx = None  # free the previous index before rebuilding — two
        # live 1M indexes force HBM defrag stalls (observed 40x outliers)
        t0 = time.perf_counter()
        fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024), X)
        fence(fidx.data)
        builds.append(time.perf_counter() - t0)
    builds.sort()
    _emit("ivf_build_1m_s", float(np.median(builds)), "s", 1.0,
          first_call_s=round(warm, 1),
          spread_pct=round((builds[-1] - builds[0])
                           / max(np.median(builds), 1e-9) * 100, 1))

    # Query regimes: clustered (db point + sigma=1 noise) and uniform.
    qc = jnp.asarray(np.asarray(X[:1000])
                     + rng.normal(size=(1000, 128)).astype(np.float32))
    qu = jnp.asarray(rng.normal(size=(1000, 128)).astype(np.float32) * 10)
    truth = {}
    for name, q in (("clustered", qc), ("uniform", qu)):
        _, ti = brute_force.knn(X, q, 10)
        truth[name] = np.asarray(ti)

    # Index tensors ride as scan arguments (a closure would bake ~0.5 GB
    # of constants into the compiled program; see _family).
    # bucket_cap=0 resolves to the round-4 packed-cells tier.
    sp = ivf_flat.SearchParams(n_probes=32, engine="bucketed")

    def flat_search(q, centers, data, indices, sizes):
        idx = ivf_flat.Index(metric=fidx.metric, centers=centers,
                             data=data, indices=indices, list_sizes=sizes)
        return ivf_flat.search(sp, idx, q, 10)

    for qname, q in (("clustered", qc), ("uniform", qu)):
        d, i = ivf_flat.search(sp, fidx, q, 10)
        rec = _recall(np.asarray(i), truth[qname])
        st = scan_stats(flat_search, q,
                        (fidx.centers, fidx.data, fidx.indices,
                         fidx.list_sizes), iters=64, repeats=3)
        _emit(f"ivf_flat_1m_qps_{qname}", 1000 / st["median_s"], "qps",
              1.0, recall_at_10=round(rec, 3), n_probes=32,
              spread_pct=_spread(st))

    # Sharded sanity at 1M (VERDICT r5 item 1 "done" bar): the same index
    # on a 1-device mesh must track single-chip QPS — the sharded body
    # now runs the production cells engine + the merge collective.
    from jax.sharding import Mesh

    from raft_tpu.parallel import ShardedIvfFlat, sharded_ivf_flat_search
    mesh1 = Mesh(np.array(jax.devices()[:1]), ("data",))
    shidx = ShardedIvfFlat(metric=fidx.metric, centers=fidx.centers,
                           data=fidx.data[None], indices=fidx.indices[None],
                           list_sizes=fidx.list_sizes[None])
    d, i = sharded_ivf_flat_search(mesh1, sp, shidx, qc, 10)
    rec = _recall(np.asarray(i), truth["clustered"])
    qps, spread = _eager_qps(
        lambda qq: sharded_ivf_flat_search(mesh1, sp, shidx, qq, 10), qc)
    _emit("ivf_flat_1m_qps_sharded1", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32, mesh_devices=1,
          spread_pct=round(spread, 1))
    del fidx, shidx

    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=1024), X)
    pidx.compressed_scan_operands()  # cache once, outside the timed loops

    # Tracked PQ metrics measure the round-4 compressed-domain tier
    # (memory = packed codes + scan operands — ivf_pq_search.cuh:611
    # parity); the recon tier (decompressed bf16 cache) is tracked
    # separately below. The clustered row and the uniform _native row
    # are the unrefined engine; the headline uniform row requests the
    # 0.86 recall class and the engine refines internally (min_recall —
    # no caller-side "refined" spelling; VERDICT r4 item 2 / r5 item 2).
    spq = ivf_pq.SearchParams(n_probes=32, engine="bucketed",
                              bucket_cap=256)
    for qname, q in (("clustered", qc), ("uniform_native", qu)):
        d, i = ivf_pq.search(spq, pidx, q, 10)
        rec = _recall(np.asarray(i), truth[qname.split("_")[0]])
        qps, spread = _eager_qps(
            lambda qq: ivf_pq.search(spq, pidx, qq, 10), q)
        _emit(f"ivf_pq_1m_qps_{qname}", qps, "qps", 1.0,
              recall_at_10=round(rec, 3), n_probes=32, engine="compressed",
              spread_pct=round(spread, 1))

    # int8 LUT flag (ISSUE 14): quantized codeword tables on the same
    # compressed tier — the recall trade recorded next to the f32 rows.
    sp8 = ivf_pq.SearchParams(n_probes=32, engine="bucketed",
                              bucket_cap=256, compressed_lut_int8=True)
    pidx.compressed_scan_operands(int8_lut=True)  # cache outside loops
    d, i = ivf_pq.search(sp8, pidx, qc, 10)
    rec = _recall(np.asarray(i), truth["clustered"])
    qps, spread = _eager_qps(
        lambda qq: ivf_pq.search(sp8, pidx, qq, 10), qc)
    _emit("ivf_pq_1m_qps_clustered_int8lut", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32,
          engine="compressed+int8lut", spread_pct=round(spread, 1))

    spr = ivf_pq.SearchParams(n_probes=32, engine="bucketed",
                              bucket_cap=256, min_recall=0.86)
    d, i = ivf_pq.search(spr, pidx, qu, 10)
    rec = _recall(np.asarray(i), truth["uniform"])
    qps, spread = _eager_qps(
        lambda qq: ivf_pq.search(spr, pidx, qq, 10), qu)
    _emit("ivf_pq_1m_qps_uniform", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), min_recall=0.86,
          engine="compressed+refine", spread_pct=round(spread, 1))

    # Sharded sanity for PQ (compressed tier per shard + merge).
    from raft_tpu.parallel import ShardedIvfPq, sharded_ivf_pq_search
    shp = ShardedIvfPq(
        metric=pidx.metric, codebook_kind=pidx.codebook_kind,
        centers=pidx.centers, rotation_matrix=pidx.rotation_matrix,
        pq_centers=pidx.pq_centers, pq_codes=pidx.pq_codes[None],
        indices=pidx.indices[None], list_sizes=pidx.list_sizes[None],
        pq_bits=pidx.pq_bits, pq_dim=pidx.pq_dim)
    d, i = sharded_ivf_pq_search(mesh1, spq, shp, qc, 10)
    rec = _recall(np.asarray(i), truth["clustered"])
    qps, spread = _eager_qps(
        lambda qq: sharded_ivf_pq_search(mesh1, spq, shp, qq, 10), qc)
    _emit("ivf_pq_1m_qps_sharded1", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32, mesh_devices=1,
          spread_pct=round(spread, 1))
    del X, shp

    # Recon tier (decompressed bf16 cache — the r3 default), kept tracked.
    fence(pidx.reconstructed())
    d, i = ivf_pq.search(spq, pidx, qc, 10)
    rec = _recall(np.asarray(i), truth["clustered"])
    qps, spread = _eager_qps(
        lambda qq: ivf_pq.search(spq, pidx, qq, 10), qc)
    _emit("ivf_pq_1m_qps_clustered_recon", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32, engine="recon",
          spread_pct=round(spread, 1))
    del pidx


def _family_4m():
    """Beyond the old recon-cache budget: 4M×128 (decompressed bf16 form
    ≈ 4.3 GB > the r3 4 GB auto budget) through the compressed-domain
    tier — the regime that previously had no fast path (254 QPS on-the-
    fly decode; VERDICT r4 item 1 asks for a >4GB-index config in the
    tracked bench). Memory stays packed codes + scan operands."""
    import jax
    import jax.numpy as jnp

    from bench.common import fence
    from raft_tpu.neighbors import brute_force, ivf_pq
    from raft_tpu.random import make_blobs

    rng = np.random.default_rng(5)
    X, _ = make_blobs(4_000_000, 128, n_clusters=2000, cluster_std=5.0,
                      seed=11)
    X = jnp.asarray(X)
    fence(X)
    q = jnp.asarray(np.asarray(X[:1000])
                    + rng.normal(size=(1000, 128)).astype(np.float32))
    _, ti = brute_force.knn(X, q, 10)
    truth = np.asarray(ti)

    t0 = time.perf_counter()
    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=2048), X)
    fence(pidx.pq_codes)
    build_s = time.perf_counter() - t0
    del X
    pidx.compressed_scan_operands()
    spq = ivf_pq.SearchParams(n_probes=32, engine="bucketed")
    d, i = ivf_pq.search(spq, pidx, q, 10)
    rec = _recall(np.asarray(i), truth)
    qps, spread = _eager_qps(
        lambda qq: ivf_pq.search(spq, pidx, qq, 10), q, reps=8)
    _emit("ivf_pq_4m_qps_clustered", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32, engine="compressed",
          build_s=round(build_s, 1), spread_pct=round(spread, 1))


def _family_sift1m_u8():
    """SIFT-format u8 end-to-end: a 1M×128 uint8 dataset flows through the
    native bvecs writer/reader (native/host_runtime.cpp — the reference's
    SIFT-shaped bench culture, cpp/bench/neighbors/knn.cuh params), builds
    u8-storage IVF-Flat and IVF-PQ indexes, and reports search QPS +
    recall@10 (VERDICT r4 item 5: every prior 1M number was synthetic
    make_blobs f32)."""
    import os

    import jax
    import jax.numpy as jnp

    from raft_tpu import _native
    from raft_tpu.neighbors import brute_force, ivf_flat, ivf_pq

    n, d, n_q = 1_000_000, 128, 1_000
    path = "/tmp/raft_tpu_sift1m.bvecs"
    qpath = "/tmp/raft_tpu_sift1m_q.bvecs"
    if not (os.path.exists(path) and os.path.exists(qpath)):
        # SIFT-like u8: clustered non-negative descriptors (host-side —
        # regenerating device-side would dodge the IO path under test).
        rng = np.random.default_rng(11)
        centers = rng.uniform(20.0, 200.0, size=(1000, d))
        assign = rng.integers(0, 1000, size=n)
        db_h = np.clip(centers[assign]
                       + rng.normal(scale=18.0, size=(n, d)),
                       0, 255).astype(np.uint8)
        qsel = rng.integers(0, n, size=n_q)
        q_h = np.clip(db_h[qsel].astype(np.float64)
                      + rng.normal(scale=6.0, size=(n_q, d)),
                      0, 255).astype(np.uint8)
        _native.write_bvecs(path, db_h)
        _native.write_bvecs(qpath, q_h)
        del db_h, q_h
    db_u8 = _native.read_bvecs(path)
    q_u8 = _native.read_bvecs(qpath)
    assert db_u8.shape == (n, d) and q_u8.shape == (n_q, d)

    X = jax.device_put(db_u8)
    Q = jax.device_put(q_u8.astype(np.float32))
    _, ti = brute_force.knn(X.astype(jnp.float32), Q, 10)
    truth = np.asarray(ti)

    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=1024), X)
    assert fidx.data.dtype == np.uint8          # quantized at rest
    spf = ivf_flat.SearchParams(n_probes=32, engine="bucketed")
    _, i = ivf_flat.search(spf, fidx, Q, 10)
    rec = _recall(np.asarray(i), truth)
    qps, spread = _eager_qps(
        lambda q: ivf_flat.search(spf, fidx, q, 10), Q, reps=12)
    _emit("ivf_flat_sift1m_u8_qps", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32,
          spread_pct=round(spread, 1))
    del fidx

    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=1024), X)
    spq = ivf_pq.SearchParams(n_probes=32, engine="bucketed",
                              bucket_cap=256)
    _, i = ivf_pq.search(spq, pidx, Q, 10)
    rec = _recall(np.asarray(i), truth)
    qps, spread = _eager_qps(
        lambda q: ivf_pq.search(spq, pidx, q, 10), Q, reps=12)
    _emit("ivf_pq_sift1m_u8_qps", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32,
          spread_pct=round(spread, 1))

    # The real-format dataset through the refine recipe (VERDICT r5
    # item 5b). SIFT-shaped clustered data concentrates the true pool
    # in the query's own list, so the robust recall class (> 0.9:
    # unbounded pool-deep queue) is the one that demonstrates the
    # recipe here — the fast bounded class is a structureless-regime
    # recipe (see ivf_pq._compressed_search).
    spr = ivf_pq.SearchParams(n_probes=32, engine="bucketed",
                              bucket_cap=256, min_recall=0.95)
    _, i = ivf_pq.search(spr, pidx, Q, 10)
    rec = _recall(np.asarray(i), truth)
    qps, spread = _eager_qps(
        lambda q: ivf_pq.search(spr, pidx, q, 10), Q, reps=12)
    _emit("ivf_pq_sift1m_u8_qps_refined", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), min_recall=0.95,
          engine="compressed+refine", spread_pct=round(spread, 1))
    del pidx


def _family_10m():
    """10M×128 compressed-domain config (VERDICT r5 item 8): packed codes
    ≈ 640 MB; the decompressed-bf16 form (~2.6 GB + a 2× f32 transient)
    is past what the recon tier could hold alongside the dataset — this
    row proves the no-decompression memory story at a scale the recon
    tier could never touch (the reference's answer is managed-memory
    spill, detail/ivf_pq_build.cuh:1108-1124; ours is native capacity).
    Built with retain_dataset=False so the index holds packed codes +
    scan operands only."""
    import jax
    import jax.numpy as jnp

    from bench.common import fence
    from raft_tpu.neighbors import brute_force, ivf_pq
    from raft_tpu.random import make_blobs

    rng = np.random.default_rng(17)
    X, _ = make_blobs(10_000_000, 128, n_clusters=4000, cluster_std=5.0,
                      seed=23)
    X = jnp.asarray(X)
    fence(X)
    q = jnp.asarray(np.asarray(X[:1000])
                    + rng.normal(size=(1000, 128)).astype(np.float32))
    _, ti = brute_force.knn(X, q, 10)
    truth = np.asarray(ti)

    t0 = time.perf_counter()
    # trainset_fraction 0.05 = 500K training rows (ample for 4096
    # clusters); the default 0.5 would stage a 2.6 GB trainset copy next
    # to the 5.1 GB dataset and OOM the 16 GB chip.
    pidx = ivf_pq.build(
        ivf_pq.IndexParams(n_lists=4096, retain_dataset=False,
                           kmeans_trainset_fraction=0.05), X)
    fence(pidx.pq_codes)
    build_s = time.perf_counter() - t0
    del X  # the index retains nothing — codes + model only
    pidx.compressed_scan_operands()
    spq = ivf_pq.SearchParams(n_probes=32, engine="bucketed")
    d, i = ivf_pq.search(spq, pidx, q, 10)
    rec = _recall(np.asarray(i), truth)
    qps, spread = _eager_qps(
        lambda qq: ivf_pq.search(spq, pidx, qq, 10), q, reps=6, rounds=5)
    _emit("ivf_pq_10m_qps_clustered", qps, "qps", 1.0,
          recall_at_10=round(rec, 3), n_probes=32, engine="compressed",
          build_s=round(build_s, 1), spread_pct=round(spread, 1))


def _family_serve():
    """Online-serving runtime metrics (ISSUE 5): steady-state served QPS
    per scheduler max_batch vs the per-request baseline, padded-slot
    waste of the pow2 bucket grid, exact-query cache hit rate, and the
    one-time warmup cost. Body lives in bench/serve.py (shared with the
    tier-1 smoke test)."""
    from bench.serve import run

    run(quick=False)


def _family_lifecycle():
    """Mutable-index lifecycle metrics (ISSUE 8): upsert churn
    throughput, search QPS vs tombstone fraction, compaction pass cost,
    and serve p99 with a compaction publish landing mid-stream. Body
    lives in bench/lifecycle.py (shared with the tier-1 smoke test)."""
    from bench.lifecycle import run

    run(quick=False)


def _family_analyze():
    """Static-gate metrics (ISSUE 9): full-tree graft-analyze wall
    time cold (fresh cache) vs warm (incremental cache hit) and the
    resulting speedup — the gate runs on every CI invocation, so its
    cost is tracked like any hot path.  Body lives in bench/analyze.py
    (shared with the tier-1 smoke test)."""
    from bench.analyze import run

    run(quick=False)


def _family_obs():
    """Observability-overhead metrics (ISSUE 11): tracer-on vs
    tracer-off serving QPS delta, full-registry scrape cost, and
    recall-probe overhead at 1% sampling.  Body lives in bench/obs.py
    (shared with the tier-1 smoke test)."""
    from bench.obs import run

    run(quick=False)


def _family_sharded():
    """Merge-engine metrics for the sharded search paths (ISSUE 1): QPS +
    estimated per-device exchange bytes per engine (allgather | ring |
    ring_bf16) over the full mesh, so the BENCH trajectory tracks the
    hierarchical merge collective's comm-volume win. Body lives in
    bench/sharded.py (shared with the tier-1 smoke test)."""
    from bench.sharded import run

    run(quick=False)


def _family_routing():
    """Probe-locality routing metrics (ISSUE 15): QPS, mean shard
    fan-out, and estimated exchange bytes for placement="list" vs the
    row-sharded baseline at uniform / clustered / hot query draws.
    Body lives in bench/sharded.py (shared with the tier-1 smoke)."""
    from bench.sharded import run_routing

    run_routing(quick=False)


def _family_degrade():
    """Tail-robustness metrics (ISSUE 19): p99 + coverage with a 10x
    straggler under hedged vs unhedged dispatch, recall-vs-latency down
    the brownout ladder's n_probes rungs, and circuit-breaker
    re-admission cost. Body lives in bench/degrade.py (shared with the
    tier-1 smoke test)."""
    from bench.degrade import run

    run(quick=False)


def _sift_like(n_db=10_000, n_q=1_000, dim=128, seed=0):
    rng = np.random.default_rng(seed)
    db = rng.integers(0, 256, size=(n_db, dim)).astype(np.float32)
    q = rng.integers(0, 256, size=(n_q, dim)).astype(np.float32)
    return db, q


def _numpy_knn_qps(db, q, k, reps=3):
    def run():
        d = ((q * q).sum(1)[:, None] + (db * db).sum(1)[None, :]
             - 2.0 * q @ db.T)
        return np.argpartition(d, k, axis=1)[:, :k]

    run()
    t0 = time.perf_counter()
    for _ in range(reps):
        run()
    return q.shape[0] / ((time.perf_counter() - t0) / reps)


def _headline():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from raft_tpu.neighbors import brute_force

    k = 10
    R = 512
    db_h, q_h = _sift_like()
    db = jax.device_put(db_h)
    q0 = jax.device_put(q_h)

    @jax.jit
    def run_all(q0, db):
        def body(acc, i):
            d, idx = brute_force.knn(db, q0 + i * jnp.float32(1e-4), k)
            return acc + d[0, 0] + idx[0, 0].astype(jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0),
                          jnp.arange(R, dtype=jnp.float32))
        d0, i0 = brute_force.knn(db, q0, k)
        return acc, d0, i0

    acc, d0, i0 = run_all(q0, db)
    np.asarray(acc)
    best = np.inf
    for _ in range(4):
        t0 = time.perf_counter()
        acc, d0, i0 = run_all(q0, db)
        np.asarray(acc)
        best = min(best, (time.perf_counter() - t0) / R)
    qps = q_h.shape[0] / best

    dn = ((q_h * q_h).sum(1)[:, None] + (db_h * db_h).sum(1)[None, :]
          - 2.0 * q_h @ db_h.T)
    truth = np.argsort(dn, axis=1)[:, :k]
    found = np.asarray(i0)
    hits = sum(len(np.intersect1d(found[r], truth[r]))
               for r in range(q_h.shape[0]))
    recall = hits / truth.size
    if recall < 0.999:
        print(json.dumps({"metric": "bf_knn_sift10k_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": f"recall {recall:.4f} < 1.0"}))
        sys.exit(1)

    cpu_qps = _numpy_knn_qps(db_h, q_h, k)
    _emit("bf_knn_sift10k_qps", qps, "qps", qps / cpu_qps)


def _run_family(fn, error_metric):
    """Run one bench family; failures emit an error row instead of
    killing the rest. The exception (whose traceback frames pin the
    family's device arrays — observed: a 10M family OOM kept 5 GB alive
    and then OOM'd the HEADLINE) is cleared and the frames collected
    before the next family runs."""
    import gc

    try:
        fn()
    except Exception as e:
        print(json.dumps({"metric": error_metric,
                          "value": 0.0, "unit": "", "vs_baseline": 0.0,
                          "error": repr(e)[:200]}), flush=True)
    gc.collect()


def main():
    # Persistent XLA cache: round-over-round bench runs skip recompilation
    # (the precompiled-instantiation role of the reference's libraft.so).
    from raft_tpu.core.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    _run_family(_family, "bench_family_error")
    _run_family(_family_analyze, "bench_analyze_error")
    if "--no-1m" not in sys.argv:
        _run_family(_family_sharded, "bench_sharded_error")
        _run_family(_family_routing, "bench_routing_error")
        _run_family(_family_serve, "bench_serve_error")
        _run_family(_family_obs, "bench_obs_error")
        _run_family(_family_lifecycle, "bench_lifecycle_error")
        _run_family(_family_degrade, "bench_degrade_error")
        _run_family(_family_1m, "bench_1m_error")
        _run_family(_family_sift1m_u8, "bench_sift1m_error")
        _run_family(_family_4m, "bench_4m_error")
        _run_family(_family_10m, "bench_10m_error")
    _headline()


if __name__ == "__main__":
    main()
