#!/usr/bin/env python
"""Round benchmark: one JSON line per tracked metric, headline LAST.

The driver parses the final stdout line ({"metric", "value", "unit",
"vs_baseline"}); the preceding lines carry the rest of the tracked family
(distance, select_k, fused_l2_nn, IVF-Flat/PQ search, balanced k-means) so
BENCH_r*.json records round-over-round movement for the whole surface, not
just the headline (the gbench-family role of cpp/bench/*). Heavyweight 1M
build/recall tables live in BASELINE.md (measured per round; the
methodology note there covers the device-link amortization).

``vs_baseline`` is the ratio against the round-1 measured value of the same
config (BASELINE.md round-1 table); the headline keeps its original
vs-NumPy-CPU baseline. Metrics new this round report vs_baseline = 1.0.
"""

import json
import sys
import time

import numpy as np

# Round-1 measured values (BASELINE.md) for vs_baseline ratios.
_R1 = {
    "pairwise_cosine_2048_gpairs": 2.9,        # G pairs/s
    "select_k_b1000_l10000_krows": 372_000.0,  # rows/s
    "fused_l2_nn_8192x1024_rows": 4_400_000.0, # rows/s
    "ivf_flat_search_100k_qps": 56_000.0,      # best round-1 bucketed
    "ivf_pq_search_100k_qps": 32_000.0,
    "kmeans_balanced_fit_100k_s": 6.6,         # best round-1 wall seconds
}


def _emit(metric, value, unit, vs):
    print(json.dumps({"metric": metric, "value": round(float(value), 1),
                      "unit": unit, "vs_baseline": round(float(vs), 3)}),
          flush=True)


def _loop_qps(fn, n_queries, reps=5):
    """Dispatch ``reps`` calls, sync once — pipelined async dispatch keeps
    the ~100 ms link round-trip out of the steady-state per-call time."""
    import jax

    jax.block_until_ready(fn())  # warm/compile
    t0 = time.perf_counter()
    out = None
    for _ in range(reps):
        out = fn()
    jax.block_until_ready(out)
    return n_queries / ((time.perf_counter() - t0) / reps)


def _family():
    import jax
    import jax.numpy as jnp

    from bench.common import scan_time, wall_time
    from raft_tpu.cluster import kmeans_balanced
    from raft_tpu.cluster.kmeans_types import KMeansBalancedParams
    from raft_tpu.distance.fused_l2_nn import fused_l2_nn_min_reduce
    from raft_tpu.distance.pairwise import distance as pairwise
    from raft_tpu.distance.distance_types import DistanceType
    from raft_tpu.matrix.select_k import select_k
    from raft_tpu.neighbors import ivf_flat, ivf_pq
    from raft_tpu.random.make_blobs import make_blobs

    rng = np.random.default_rng(0)

    # distance: cosine 2048x2048x128 (G pairs/s)
    a = jnp.asarray(rng.normal(size=(2048, 128)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(2048, 128)).astype(np.float32))
    s = scan_time(lambda x: pairwise(x, b, metric=DistanceType.CosineExpanded),
                  a, iters=32)
    v = 2048 * 2048 / s / 1e9
    _emit("pairwise_cosine_2048_gpairs", v, "Gpairs/s",
          v / _R1["pairwise_cosine_2048_gpairs"])

    # select_k: batch 1000, len 10000, k 10 (rows/s)
    m = jnp.asarray(rng.normal(size=(1000, 10000)).astype(np.float32))
    s = scan_time(lambda x: select_k(x, 10), m, iters=32)
    v = 1000 / s
    _emit("select_k_b1000_l10000_krows", v, "rows/s",
          v / _R1["select_k_b1000_l10000_krows"])

    # fused_l2_nn: 8192x1024x64 (rows/s)
    x = jnp.asarray(rng.normal(size=(8192, 64)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(1024, 64)).astype(np.float32))
    s = scan_time(lambda q: fused_l2_nn_min_reduce(q, y), x, iters=32)
    v = 8192 / s
    _emit("fused_l2_nn_8192x1024_rows", v, "rows/s",
          v / _R1["fused_l2_nn_8192x1024_rows"])

    # IVF search QPS at 100K x 128 (explicit bucket_cap: the tuned engine;
    # recall parity for these configs is pinned by tests + BASELINE.md)
    X, _ = make_blobs(100_000, 128, n_clusters=200, seed=3)
    X = X.block_until_ready()
    Q = X[:1000]
    fidx = ivf_flat.build(ivf_flat.IndexParams(n_lists=256), X)
    jax.block_until_ready(fidx.data)
    spf = ivf_flat.SearchParams(n_probes=32, engine="bucketed",
                                bucket_cap=128)
    v = _loop_qps(lambda: ivf_flat.search(spf, fidx, Q, 10), 1000)
    _emit("ivf_flat_search_100k_qps", v, "qps",
          v / _R1["ivf_flat_search_100k_qps"])

    pidx = ivf_pq.build(ivf_pq.IndexParams(n_lists=256), X)
    jax.block_until_ready(pidx.pq_centers)
    spq = ivf_pq.SearchParams(n_probes=32, engine="bucketed", bucket_cap=128)
    v = _loop_qps(lambda: ivf_pq.search(spq, pidx, Q, 10), 1000)
    _emit("ivf_pq_search_100k_qps", v, "qps",
          v / _R1["ivf_pq_search_100k_qps"])

    # balanced k-means fit: 100K x 64, k=512 (wall seconds; lower=better,
    # vs_baseline reported as speedup ratio r1/now)
    Xk, _ = make_blobs(100_000, 64, n_clusters=100, seed=7)
    Xk = Xk.block_until_ready()
    p = KMeansBalancedParams(n_iters=10)
    s = wall_time(lambda: kmeans_balanced.fit(p, Xk, 512))
    _emit("kmeans_balanced_fit_100k_s", s, "s",
          _R1["kmeans_balanced_fit_100k_s"] / s)

    # sparse pairwise L2, 2048 x 2048 at 50k dims, ~0.1% dense (block-staged
    # engine; round 1 densified and could not run this shape) — wall seconds,
    # new this round (vs_baseline = 1.0 by definition)
    from raft_tpu.sparse import distance as sparse_distance
    from raft_tpu.sparse.types import CSR

    d_sp, nnz_row, rows = 50_000, 50, 2048
    cols = rng.integers(0, d_sp, size=rows * nnz_row).astype(np.int32)
    valsv = rng.normal(size=rows * nnz_row).astype(np.float32)
    indptr = np.arange(0, rows * nnz_row + 1, nnz_row, dtype=np.int32)
    ca = CSR(jnp.asarray(indptr), jnp.asarray(cols), jnp.asarray(valsv),
             (rows, d_sp))
    s = wall_time(lambda: sparse_distance.pairwise_distance(
        ca, ca, metric="euclidean"))
    _emit("sparse_l2_2048x50kd_s", s, "s", 1.0)


def _sift_like(n_db=10_000, n_q=1_000, dim=128, seed=0):
    rng = np.random.default_rng(seed)
    db = rng.integers(0, 256, size=(n_db, dim)).astype(np.float32)
    q = rng.integers(0, 256, size=(n_q, dim)).astype(np.float32)
    return db, q


def _numpy_knn_qps(db, q, k, reps=3):
    def run():
        d = ((q * q).sum(1)[:, None] + (db * db).sum(1)[None, :]
             - 2.0 * q @ db.T)
        return np.argpartition(d, k, axis=1)[:, :k]

    run()
    t0 = time.perf_counter()
    for _ in range(reps):
        run()
    return q.shape[0] / ((time.perf_counter() - t0) / reps)


def _headline():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from raft_tpu.neighbors import brute_force

    k = 10
    R = 512
    db_h, q_h = _sift_like()
    db = jax.device_put(db_h)
    q0 = jax.device_put(q_h)

    @jax.jit
    def run_all(q0, db):
        def body(acc, i):
            d, idx = brute_force.knn(db, q0 + i * jnp.float32(1e-4), k)
            return acc + d[0, 0] + idx[0, 0].astype(jnp.float32), None

        acc, _ = lax.scan(body, jnp.float32(0),
                          jnp.arange(R, dtype=jnp.float32))
        d0, i0 = brute_force.knn(db, q0, k)
        return acc, d0, i0

    acc, d0, i0 = run_all(q0, db)
    np.asarray(acc)
    best = np.inf
    for _ in range(4):
        t0 = time.perf_counter()
        acc, d0, i0 = run_all(q0, db)
        np.asarray(acc)
        best = min(best, (time.perf_counter() - t0) / R)
    qps = q_h.shape[0] / best

    dn = ((q_h * q_h).sum(1)[:, None] + (db_h * db_h).sum(1)[None, :]
          - 2.0 * q_h @ db_h.T)
    truth = np.argsort(dn, axis=1)[:, :k]
    found = np.asarray(i0)
    hits = sum(len(np.intersect1d(found[r], truth[r]))
               for r in range(q_h.shape[0]))
    recall = hits / truth.size
    if recall < 0.999:
        print(json.dumps({"metric": "bf_knn_sift10k_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": f"recall {recall:.4f} < 1.0"}))
        sys.exit(1)

    cpu_qps = _numpy_knn_qps(db_h, q_h, k)
    _emit("bf_knn_sift10k_qps", qps, "qps", qps / cpu_qps)


def main():
    # Persistent XLA cache: round-over-round bench runs skip recompilation
    # (the precompiled-instantiation role of the reference's libraft.so).
    from raft_tpu.core.compilation_cache import enable_compilation_cache

    enable_compilation_cache()
    try:
        _family()
    except Exception as e:  # family failures must not kill the headline
        print(json.dumps({"metric": "bench_family_error",
                          "value": 0.0, "unit": "", "vs_baseline": 0.0,
                          "error": repr(e)[:200]}), flush=True)
    _headline()


if __name__ == "__main__":
    main()
