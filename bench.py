#!/usr/bin/env python
"""Headline benchmark: brute-force k-NN QPS (fused L2 + top-k) on SIFT-like
data — BASELINE.json config #2.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference repo publishes no benchmark numbers (BASELINE.md — RAFT 23.04
has only gbench microbenchmarks, no results tables), so ``vs_baseline``
compares against a CPU/NumPy exact-kNN implementation of the same workload
measured in-process — the honest available baseline on this hardware.
"""

import json
import sys
import time

import numpy as np


def _sift_like(n_db=10_000, n_q=1_000, dim=128, seed=0):
    """SIFT-10K-shaped synthetic data (uint8-range descriptors)."""
    rng = np.random.default_rng(seed)
    db = rng.integers(0, 256, size=(n_db, dim)).astype(np.float32)
    q = rng.integers(0, 256, size=(n_q, dim)).astype(np.float32)
    return db, q


def _numpy_knn_qps(db, q, k, reps=3):
    def run():
        d = (
            (q * q).sum(1)[:, None]
            + (db * db).sum(1)[None, :]
            - 2.0 * q @ db.T
        )
        idx = np.argpartition(d, k, axis=1)[:, :k]
        return idx

    run()
    t0 = time.perf_counter()
    for _ in range(reps):
        run()
    dt = (time.perf_counter() - t0) / reps
    return q.shape[0] / dt


def main():
    import jax

    from raft_tpu.neighbors import brute_force

    k = 10
    db_h, q_h = _sift_like()
    db = jax.device_put(db_h)
    q = jax.device_put(q_h)

    # Warmup (compile) then timed runs.
    d, i = brute_force.knn(db, q, k)
    jax.block_until_ready((d, i))
    reps = 10
    t0 = time.perf_counter()
    for _ in range(reps):
        d, i = brute_force.knn(db, q, k)
        jax.block_until_ready((d, i))
    dt = (time.perf_counter() - t0) / reps
    qps = q.shape[0] / dt

    # Correctness gate: recall@10 == 1.0 vs exact NumPy ground truth.
    dn = ((q_h[:, None, :] - db_h[None]) ** 2).sum(-1)
    truth = np.argsort(dn, axis=1)[:, :k]
    found = np.asarray(i)
    hits = sum(len(np.intersect1d(found[r], truth[r])) for r in range(q_h.shape[0]))
    recall = hits / truth.size
    if recall < 0.999:
        print(json.dumps({"metric": "bf_knn_sift10k_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": f"recall {recall:.4f} < 1.0"}))
        sys.exit(1)

    cpu_qps = _numpy_knn_qps(db_h, q_h, k)
    print(json.dumps({
        "metric": "bf_knn_sift10k_qps",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 3),
    }))


if __name__ == "__main__":
    main()
