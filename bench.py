#!/usr/bin/env python
"""Headline benchmark: brute-force k-NN QPS (fused L2 + top-k) on SIFT-like
data — BASELINE.json config #2.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The reference repo publishes no benchmark numbers (BASELINE.md — RAFT 23.04
has only gbench microbenchmarks, no results tables), so ``vs_baseline``
compares against a CPU/NumPy exact-kNN implementation of the same workload
measured in-process — the honest available baseline on this hardware.

Timing methodology: the device link (axon tunnel) has ~100 ms round-trip
latency per synchronized call and ``block_until_ready`` does not reliably
fence it, so the workload is iterated R times *inside one jit* via
``lax.scan``, with the query batch perturbed by the scan index so XLA can
neither hoist nor cache the body, and synced once with a host transfer.
Per-iteration time = total / R with the link overhead amortized (the analog
of the reference's cudaEvent timing with L2-flush between iterations,
cpp/bench/common/benchmark.hpp:93-148).
"""

import json
import sys
import time

import numpy as np


def _sift_like(n_db=10_000, n_q=1_000, dim=128, seed=0):
    """SIFT-10K-shaped synthetic data (uint8-range descriptors)."""
    rng = np.random.default_rng(seed)
    db = rng.integers(0, 256, size=(n_db, dim)).astype(np.float32)
    q = rng.integers(0, 256, size=(n_q, dim)).astype(np.float32)
    return db, q


def _numpy_knn_qps(db, q, k, reps=3):
    def run():
        d = (
            (q * q).sum(1)[:, None]
            + (db * db).sum(1)[None, :]
            - 2.0 * q @ db.T
        )
        idx = np.argpartition(d, k, axis=1)[:, :k]
        return idx

    run()
    t0 = time.perf_counter()
    for _ in range(reps):
        run()
    dt = (time.perf_counter() - t0) / reps
    return q.shape[0] / dt


def main():
    import jax
    import jax.numpy as jnp
    from jax import lax

    from raft_tpu.neighbors import brute_force

    k = 10
    R = 512  # iterations per synchronized run: amortizes the ~100 ms
    # axon-link round-trip to ~0.2 ms/iteration
    db_h, q_h = _sift_like()
    db = jax.device_put(db_h)
    q0 = jax.device_put(q_h)

    @jax.jit
    def run_all(q0, db):
        # Perturb the query batch per step (anti-hoisting: the body must
        # depend on the scan index) — the timing analog of the reference's
        # L2-flush between iterations (cpp/bench/common/benchmark.hpp).
        def body(acc, i):
            d, idx = brute_force.knn(db, q0 + i * jnp.float32(1e-4), k)
            return acc + d[0, 0] + idx[0, 0].astype(jnp.float32), None
        acc, _ = lax.scan(body, jnp.float32(0),
                          jnp.arange(R, dtype=jnp.float32))
        d0, i0 = brute_force.knn(db, q0, k)  # unperturbed: correctness gate
        return acc, d0, i0

    # Warmup (compile) + one synced run, then timed runs (sync via host
    # transfer of the checksum scalar).
    acc, d0, i0 = run_all(q0, db)
    np.asarray(acc)
    best = np.inf
    for _ in range(4):
        t0 = time.perf_counter()
        acc, d0, i0 = run_all(q0, db)
        np.asarray(acc)
        best = min(best, (time.perf_counter() - t0) / R)
    qps = q_h.shape[0] / best

    # Correctness gate: recall@10 == 1.0 vs exact NumPy ground truth.
    dn = ((q_h * q_h).sum(1)[:, None] + (db_h * db_h).sum(1)[None, :]
          - 2.0 * q_h @ db_h.T)
    truth = np.argsort(dn, axis=1)[:, :k]
    found = np.asarray(i0)
    hits = sum(len(np.intersect1d(found[r], truth[r]))
               for r in range(q_h.shape[0]))
    recall = hits / truth.size
    if recall < 0.999:
        print(json.dumps({"metric": "bf_knn_sift10k_qps", "value": 0.0,
                          "unit": "qps", "vs_baseline": 0.0,
                          "error": f"recall {recall:.4f} < 1.0"}))
        sys.exit(1)

    cpu_qps = _numpy_knn_qps(db_h, q_h, k)
    print(json.dumps({
        "metric": "bf_knn_sift10k_qps",
        "value": round(qps, 1),
        "unit": "qps",
        "vs_baseline": round(qps / cpu_qps, 3),
    }))


if __name__ == "__main__":
    main()
